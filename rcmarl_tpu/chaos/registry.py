"""The fault-surface registry: every injectable fault, named and runnable.

A :class:`ChaosPoint` is one place the system can be hurt — a transport
link, a gossip replica, a checkpoint byte range, a pipeline transit, a
serving queue — with the knob that hurts it, the guard that is supposed
to absorb it, and the test that pins the mechanism. Each point carries
one or more CELLS (intensity label + expected outcome); the campaign
runner (:mod:`rcmarl_tpu.chaos.campaign`) executes every cell as a
short REAL run through the actual subsystem entry points (``train``,
``train_gossip``, ``train_pipelined``, the serve engine + watcher, the
load queue) — never a mock — and classifies the result on the shared
outcome ladder:

- ``survived`` — the guards contained the fault completely: the run/
  serving stayed finite AND functionally intact (final return inside
  the clean twin's band, serving bitwise the expected policy, latency
  inside the bound). Guard counters firing is NOT degradation — cleanly
  absorbing a fault is exactly what surviving means.
- ``degraded`` — contained but measurably reduced: skipped training
  blocks, a quarantined replica, a return outside the clean band, a
  latency past the bound. Finite everywhere, bounded everywhere.
- ``failed`` — containment broke: non-finite params/serving output, a
  crash, or an assertion on the guard's contract itself. Some cells
  EXPECT ``failed`` — the undefended comparison arms (plain mean,
  H=0 under collusion) are part of the documented fault surface, and a
  regression that silently FIXES them would be as suspicious as one
  that breaks a defended cell.

This module is in the lint hot-path set so the traced-value rules bind
on any jitted inner function a scenario grows; the scenario runners
themselves are HOST harness code — every ``float()``/``np.asarray()``
here consumes a completed training result (a pandas frame, a finished
serve call) and every ``PRNGKey(int)`` mints a fixed host-side fixture
seed — so those lines carry per-line pragma waivers.

Every cell is deterministic (fixed seeds, simulated clocks, injected
service models where wall time would leak in), so the committed
``RESILIENCE.jsonl`` rows are reproducible and the ``--check`` gate
compares like with like.

Band discipline: the tiny cells are O(10)-episode runs, so the
"functionally intact" band is deliberately generous
(``RETURN_BAND = 0.5`` relative to the clean twin) — the committed
ledger's gate is on TRANSITIONS (a survived cell failing, an envelope
widening), not on the absolute label of a noisy tiny return.
"""

from __future__ import annotations

import math
import struct
import tempfile
import zipfile
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Optional, Tuple

#: The outcome ladder, worst last (the --check gate fires on any cell
#: moving RIGHT of its committed outcome).
OUTCOMES = ("survived", "degraded", "failed")

#: Relative band vs the clean twin's final return inside which a
#: faulted cell still counts as functionally intact (see module
#: docstring — generous by design at this cell size).
RETURN_BAND = 0.5

#: Final-return window: mean over the last K episodes of the tiny run.
RETURN_WINDOW = 4

#: The overload cells' latency bound: p99 must stay within this factor
#: of the knee-point p99 (the acceptance criterion of the deadline-
#: shedding feature, encoded as a gated cell).
LATENCY_BOUND_FACTOR = 2.0


class CellFailed(RuntimeError):
    """A containment contract the cell asserts was violated — the
    campaign records the cell as ``failed`` with this detail (cell
    isolation: one broken guard never aborts the sweep)."""


class ChaosSkip(RuntimeError):
    """The cell cannot run on THIS host (e.g. a hardware-only arm).
    Recorded as a note, never a stale-row finding — the cost-arm
    discipline (skipped-on-this-host is a note, not stale)."""


@dataclass(frozen=True)
class ChaosPoint:
    """One named point on the fault surface (see module docstring).

    ``cells`` maps intensity label -> expected outcome; ``runner`` is
    called with the intensity label and returns the result dict
    (``outcome``/``counters``/``final_return``/``clean_return``/
    ``detail``). ``guard``/``test_pin`` are the documentation pointers
    the unified README fault-surface table renders.
    """

    name: str
    subsystem: str
    description: str
    injector: str
    guard: str
    test_pin: str
    cells: Tuple[Tuple[str, str], ...]
    runner: Callable


# --------------------------------------------------------------------------
# shared tiny workloads + the clean-twin cache
# --------------------------------------------------------------------------

_CLEAN_CACHE: Dict[object, float] = {}


def _final_return(df) -> float:
    import numpy as np

    vals = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    return float(np.mean(vals[-RETURN_WINDOW:]))  # lint: disable=host-sync


def _within_band(final: float, clean: float) -> bool:
    return abs(final - clean) <= RETURN_BAND * max(1.0, abs(clean))


def _params_ok(state) -> bool:
    from rcmarl_tpu.faults import params_finite

    return params_finite(state.params)


def _clean_train_return(cfg, n_eps: int) -> float:
    """Memoized clean-twin final return for a faulted train cell: the
    SAME tiny config with the fault machinery stripped."""
    from rcmarl_tpu.training.trainer import train

    clean = cfg.replace(fault_plan=None, consensus_sanitize=False)
    key = ("train", clean, n_eps)
    if key not in _CLEAN_CACHE:
        _, df = train(clean, n_episodes=n_eps)
        _CLEAN_CACHE[key] = _final_return(df)
    return _CLEAN_CACHE[key]


def _tiny(**overrides):
    from rcmarl_tpu.lint.configs import tiny_cfg

    return tiny_cfg(**overrides)


# --------------------------------------------------------------------------
# transport: per-link fault plans through the real solo trainer
# --------------------------------------------------------------------------

#: (point suffix, FaultPlan field) of the probabilistic link faults.
_LINK_FAULTS = {
    "link_drop": "drop_p",
    "link_nan": "nan_p",
    "link_stale": "stale_p",
    "link_flip": "flip_p",
    "link_corrupt": "corrupt_p",
}

_TRAIN_EPS = 8  # 4 tiny blocks: enough for guards to engage and recover


def _train_cell(cfg) -> dict:
    """One guarded tiny train under ``cfg``'s fault plan, classified
    against the clean twin (transport/consensus shared core)."""
    import numpy as np

    from rcmarl_tpu.training.trainer import train

    state, df = train(cfg, n_episodes=_TRAIN_EPS)
    clean = _clean_train_return(cfg, _TRAIN_EPS)
    guard = dict(df.attrs.get("guard", {}))
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    final = _final_return(df)
    if not _params_ok(state) or not np.isfinite(returns[-RETURN_WINDOW:]).all():
        outcome = "failed"
    elif (
        guard.get("skipped", 0) > 0
        or not np.isfinite(returns).all()
        or not _within_band(final, clean)
    ):
        # lost blocks / poisoned metric rows / outside the band:
        # contained, but function was measurably reduced
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": guard,
        "final_return": None if not math.isfinite(final) else final,
        "clean_return": clean,
        "detail": f"{_TRAIN_EPS} episodes, guarded tiny train",
    }


def _run_link(fault: str, sanitize: bool, intensity: str) -> dict:
    from rcmarl_tpu.faults import FaultPlan

    p = float(intensity)  # lint: disable=host-sync
    plan = FaultPlan(**{_LINK_FAULTS[fault]: p})
    return _train_cell(
        _tiny(
            n_episodes=_TRAIN_EPS,
            fault_plan=plan,
            consensus_sanitize=sanitize,
        )
    )


def _link_runner(fault: str, sanitize: bool = True):
    return lambda intensity: _run_link(fault, sanitize, intensity)


# --------------------------------------------------------------------------
# consensus: the adaptive colluding adversary
# --------------------------------------------------------------------------


def _run_adaptive(intensity: str) -> dict:
    """``h{H}``: 1 Adaptive colluder at scale 10 in the tiny 3-ring;
    the trimmed H=1 arm must hold the band, the undefended H=0 arm is
    the documented failure surface (its clip bounds are the attack's)."""
    import numpy as np

    from rcmarl_tpu.config import Roles
    from rcmarl_tpu.training.trainer import train

    H = int(intensity.removeprefix("h"))  # lint: disable=host-sync
    cfg = _tiny(
        n_episodes=_TRAIN_EPS,
        agent_roles=(Roles.COOPERATIVE, Roles.COOPERATIVE, Roles.ADAPTIVE),
        H=H,
        adaptive_scale=10.0,
    )
    clean_key = ("adaptive_clean", H)
    if clean_key not in _CLEAN_CACHE:
        _, df = train(
            cfg.replace(agent_roles=(Roles.COOPERATIVE,) * 3),
            n_episodes=_TRAIN_EPS,
        )
        _CLEAN_CACHE[clean_key] = _final_return(df)
    clean = _CLEAN_CACHE[clean_key]
    state, df = train(cfg, n_episodes=_TRAIN_EPS, guard=False)
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    # the behavioral threat model scores the COOPERATIVE team: the
    # colluder's own row is adversary bookkeeping
    final = _final_return(df)
    if not _params_ok(state) or not np.isfinite(returns).all():
        outcome = "failed"
        final = None
    elif not _within_band(final, clean):
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": {},
        "final_return": final,
        "clean_return": clean,
        "detail": f"1 Adaptive colluder, scale 10, H={H}, guard off",
    }


def _run_mega_sparse(intensity: str) -> dict:
    """``h{H}`` / ``h{H}_fused``: adaptive collusion at POPULATION
    scale over the sparse time-varying graph — 248 cooperators + 8
    Adaptive colluders at n=256, trimmed consensus over
    random-geometric degree-9 neighborhoods resampled every block
    (gather indices flow as DATA through
    :func:`rcmarl_tpu.ops.exchange.sparse_gather`, with
    ``validate_graph`` guarding every resample on the real host-loop
    path). The ``_fused`` suffix runs the same cell on the round-19
    fused Pallas phase II (``consensus_impl='pallas_fused_interpret'``:
    the schedule rides the kernel as a scalar-prefetch operand) — the
    resilience claim must hold on the kernel arm, not just the XLA
    chain it mirrors. Survival = the trim holds the clean twin's band
    where each neighborhood sees colluders only through the sparse
    schedule — the n-scale point the tiny 3-ring adaptive cell cannot
    represent."""
    import numpy as np

    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.training.trainer import train

    fused = intensity.endswith("_fused")
    H = int(intensity.removeprefix("h").removesuffix("_fused"))  # lint: disable=host-sync
    n, n_adv = 256, 8
    base = dict(
        n_agents=n,
        agent_roles=(Roles.COOPERATIVE,) * (n - n_adv)
        + (Roles.ADAPTIVE,) * n_adv,
        in_nodes=circulant_in_nodes(n, 5),
        nrow=16,
        ncol=16,
        hidden=(4,),
        graph_schedule="random_geometric",
        graph_degree=9,
        H=H,
        fit_clip=1.0,
        adaptive_scale=10.0,
        n_episodes=_TRAIN_EPS,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=1,
    )
    # The clean all-cooperative twin is shared across consensus arms
    # (built from `base` BEFORE the impl override): the band is a
    # return comparison, not a bitwise pin, and the default-impl twin
    # is an order of magnitude cheaper than interpret-mode Pallas.
    clean_cfg = Config(**base).replace(agent_roles=(Roles.COOPERATIVE,) * n)
    if fused:
        base["consensus_impl"] = "pallas_fused_interpret"
    cfg = Config(**base)
    clean_key = ("mega_sparse_clean", H)
    if clean_key not in _CLEAN_CACHE:
        _, df = train(clean_cfg, n_episodes=_TRAIN_EPS)
        _CLEAN_CACHE[clean_key] = _final_return(df)
    clean = _CLEAN_CACHE[clean_key]
    state, df = train(cfg, n_episodes=_TRAIN_EPS, guard=False)
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    final = _final_return(df)
    if not _params_ok(state) or not np.isfinite(returns).all():
        outcome = "failed"
        final = None
    elif not _within_band(final, clean):
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": {},
        "final_return": final,
        "clean_return": clean,
        "detail": (
            f"{n_adv} Adaptive colluders at n={n}, scale 10, H={H}, "
            "random_geometric degree 9 (sparse data-graph exchange, "
            + ("fused Pallas phase II" if fused else "XLA chain")
            + "), guard off"
        ),
    }


# --------------------------------------------------------------------------
# gossip: Byzantine replicas, replica-link bombs, flapping + readmission
# --------------------------------------------------------------------------


def _gossip_cfg(**overrides):
    base = dict(
        replicas=4,
        gossip_every=1,
        gossip_graph="full",
        gossip_H=1,
        n_episodes=8,
    )
    base.update(overrides)
    return _tiny(**base)


def _gossip_cell(cfg, readmit_after: int = 0, expect_all_healthy=True) -> dict:
    import numpy as np

    from rcmarl_tpu.parallel.gossip import train_gossip

    states, df = train_gossip(cfg, readmit_after=readmit_after)
    g = df.attrs["gossip"]
    byz = set(g["byzantine"])
    healthy = [
        ok for r, ok in enumerate(g["replica_healthy"]) if r not in byz
    ]
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    final = _final_return(df)
    counters = {
        k: g[k]
        for k in ("rounds", "rollbacks", "excluded", "readmitted",
                  "nonfinite", "deficit")
    }
    # the clean twin is the SAME cell config with the fault machinery
    # stripped — mix arm and episode count included (a mean-mix or
    # longer-run cell must not measure its envelope against a trimmed
    # 8-episode twin's learning curve); Config is hashable, so the
    # stripped config IS the cache key
    clean_cfg = cfg.replace(
        fault_plan=None, replica_fault_plan=None, consensus_sanitize=False
    )
    clean_key = ("gossip_clean", clean_cfg)
    if clean_key not in _CLEAN_CACHE:
        from rcmarl_tpu.parallel.gossip import train_gossip as tg

        _, cdf = tg(clean_cfg, guard=False)
        _CLEAN_CACHE[clean_key] = _final_return(cdf)
    clean = _CLEAN_CACHE[clean_key]
    if not all(healthy) or not np.isfinite(returns[-RETURN_WINDOW:]).all():
        outcome = "failed"
        final = final if math.isfinite(final) else None
    elif g["rollbacks"] > 0 or any(g["quarantined"]) or not _within_band(
        final, clean
    ):
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": counters,
        "final_return": final,
        "clean_return": clean,
        "detail": (
            f"R={cfg.replicas} {cfg.gossip_graph} graph, "
            f"gossip_H={cfg.gossip_H}, mix={cfg.gossip_mix}, "
            f"readmit_after={readmit_after}"
        ),
    }


def _run_byzantine(intensity: str) -> dict:
    from rcmarl_tpu.faults import ReplicaFaultPlan

    return _gossip_cell(
        _gossip_cfg(
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode=intensity
            )
        )
    )


def _run_byzantine_mean(intensity: str) -> dict:
    from rcmarl_tpu.faults import ReplicaFaultPlan

    return _gossip_cell(
        _gossip_cfg(
            gossip_mix="mean",
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode=intensity
            ),
        )
    )


def _run_replica_link(intensity: str) -> dict:
    from rcmarl_tpu.faults import ReplicaFaultPlan

    return _gossip_cell(
        _gossip_cfg(
            replica_fault_plan=ReplicaFaultPlan(nan_p=float(intensity))  # lint: disable=host-sync
        )
    )


def _run_flapping(intensity: str) -> dict:
    """``readmitK``: agent-level probabilistic NaN bombs WITHOUT
    sanitize flap individual replicas unhealthy segment by segment; the
    sticky quarantine must exclude them, readmit them after K clean
    probe rounds, and keep every replica finite end to end."""
    from rcmarl_tpu.faults import FaultPlan

    K = int(intensity.removeprefix("readmit"))  # lint: disable=host-sync
    res = _gossip_cell(
        _gossip_cfg(
            n_episodes=12,
            fault_plan=FaultPlan(nan_p=0.1),
        ),
        readmit_after=K,
    )
    if res["outcome"] != "failed" and res["counters"]["rollbacks"] == 0:
        raise CellFailed(
            "flapping cell drew no rollbacks — the injection rate no "
            "longer flaps a replica; retune nan_p"
        )
    return res


# --------------------------------------------------------------------------
# checkpoint / publish: byte corruption at named positions
# --------------------------------------------------------------------------


def _member_data_offset(path, member: str) -> int:
    """Byte offset of a (stored, uncompressed) npz member's data — so
    the corruption cells can hit NAMED regions of the file (a leaf
    payload, the config header, the meta header) instead of magic
    offsets."""
    with zipfile.ZipFile(path) as z:
        info = z.getinfo(member)
    with open(path, "rb") as f:
        f.seek(info.header_offset + 26)
        n, m = struct.unpack("<HH", f.read(4))
    return info.header_offset + 30 + n + m


def _corrupt_member(path, member: str, skip: int = 96) -> None:
    """Flip a burst of bytes ``skip`` into the member's data (past the
    .npy magic/header, inside the array payload)."""
    off = _member_data_offset(path, member)
    with open(path, "r+b") as f:
        f.seek(off + skip)
        f.write(b"\xde\xad\xbe\xef" * 16)


_CKPT_MEMBER = {
    "payload": "leaf_000.npy",
    "header": "__config__.npy",
    "meta": "__meta__.npy",
}


def _run_ckpt_bitflip(intensity: str) -> dict:
    """Watcher-facing checkpoint corruption at a named position:
    single-position flips must land on the ``.prev`` fallback
    (counters correct, serving the previous good policy bitwise);
    ``truncate`` exercises the unreadable-zip path the same way;
    ``both`` (primary AND ``.prev``) must REJECT and keep serving the
    last good block; a healthy re-publish must recover either way."""
    import jax
    import numpy as np

    from rcmarl_tpu.serve.engine import (
        ServeEngine,
        serve_block,
        stack_actor_rows,
    )
    from rcmarl_tpu.serve.swap import CheckpointWatcher
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.checkpoint import save_checkpoint

    cfg = _tiny()
    state_a = init_train_state(cfg, jax.random.PRNGKey(0))  # lint: disable=prng-int-seed
    state_b = init_train_state(cfg, jax.random.PRNGKey(1))  # lint: disable=prng-int-seed
    obs = jax.random.normal(
        jax.random.PRNGKey(5), (4, cfg.n_agents, cfg.obs_dim)  # lint: disable=prng-int-seed
    )
    key = jax.random.PRNGKey(9)  # lint: disable=prng-int-seed

    def probs_of(state):
        _, p = serve_block(
            cfg, stack_actor_rows(state.params, cfg), obs, key
        )
        return np.asarray(p)  # lint: disable=host-sync

    with tempfile.TemporaryDirectory() as d:
        path = Path(d) / "checkpoint.npz"
        meta = {"replicas": 0, "origin": "chaos"}
        save_checkpoint(path, state_a, cfg, meta=meta)
        eng = ServeEngine(path)
        watcher = CheckpointWatcher(eng)
        save_checkpoint(path, state_b, cfg, meta=meta)  # rotates A -> .prev
        if intensity == "both":
            _corrupt_member(path, _CKPT_MEMBER["payload"])
            _corrupt_member(str(path) + ".prev", _CKPT_MEMBER["payload"])
        elif intensity == "truncate":
            path.write_bytes(path.read_bytes()[: path.stat().st_size // 2])
        else:
            _corrupt_member(path, _CKPT_MEMBER[intensity])
        applied = watcher.poll()
        _, p = eng.serve(obs, key=key)
        if not np.isfinite(np.asarray(p)).all():  # lint: disable=host-sync
            raise CellFailed("engine served non-finite probabilities")
        if intensity == "both":
            if applied or eng.counters["rejects"] != 1:
                raise CellFailed(
                    "double corruption was not rejected "
                    f"(applied={applied}, counters={eng.counters})"
                )
            expect = state_a  # the initial load is the last good block
        else:
            if not applied or eng.counters["fallbacks"] != 1:
                raise CellFailed(
                    "single-position corruption did not land on the "
                    f".prev fallback (applied={applied}, "
                    f"counters={eng.counters})"
                )
            expect = state_a  # .prev holds A
        if not np.array_equal(np.asarray(p), probs_of(expect)):  # lint: disable=host-sync
            raise CellFailed("served policy is not the expected block")
        # recovery: a healthy re-publish must swap in
        save_checkpoint(path, state_b, cfg, meta=meta)
        if not watcher.poll():
            raise CellFailed("healthy re-publish did not recover")
        _, p2 = eng.serve(obs, key=key)
        if not np.array_equal(np.asarray(p2), probs_of(state_b)):  # lint: disable=host-sync
            raise CellFailed("post-recovery serving is not the candidate")
        return {
            "outcome": "survived",
            "counters": dict(eng.counters),
            "final_return": None,
            "clean_return": None,
            "detail": (
                f"corrupt {intensity}; poll -> "
                + ("reject+last-good" if intensity == "both" else
                   ".prev fallback")
                + "; healthy re-publish recovers"
            ),
        }


def _run_publish_poison(intensity: str) -> dict:
    """A NaN-poisoned in-memory publish candidate must be rejected by
    the shared ``params_finite`` guard with the actor tier kept on the
    last good tree (the PolicyPublisher half of the watcher contract)."""
    import numpy as np

    from rcmarl_tpu.pipeline.publish import PolicyPublisher

    good = {"w": np.ones(8, np.float32)}
    pub = PolicyPublisher(good, validate=True)
    bad = {"w": np.full(8, np.nan, np.float32)}
    if pub.offer(bad, 1) is not False or pub.acting is not good:
        raise CellFailed("poisoned publish reached the acting tier")
    fresh = {"w": np.full(8, 2.0, np.float32)}
    if pub.offer(fresh, 2) is not True or pub.acting is not fresh:
        raise CellFailed("publisher wedged after the rejection")
    return {
        "outcome": "survived",
        "counters": dict(pub.counters),
        "final_return": None,
        "clean_return": None,
        "detail": "NaN candidate rejected, healthy re-publish promoted",
    }


# --------------------------------------------------------------------------
# pipeline: poisoned actor-tier rollout windows + faulted guarded runs
# --------------------------------------------------------------------------


def _nan_bomb_window(fresh, m):
    import jax
    import jax.numpy as jnp

    bomb = lambda l: (
        jnp.full_like(l, jnp.nan)
        if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
        else l
    )
    return jax.tree.map(bomb, fresh), m


def _run_pipeline_window(intensity: str) -> dict:
    """``transient``: block 1's dispatched window is poisoned once —
    one redraw must recover it (no skip, full publishes).
    ``persistent``: every draw of block 1 is poisoned — bounded
    redraws, then a skip with NOTHING published and the staleness
    lengthened (the skip-and-redraw contract; historically the learner
    burned its retry budget re-consuming the same poisoned window)."""
    from rcmarl_tpu.pipeline.trainer import train_pipelined

    persistent = intensity == "persistent"

    def window_fault(b, attempt, fresh, m):
        if b == 1 and (persistent or attempt == 0):
            return _nan_bomb_window(fresh, m)
        return fresh, m

    cfg = _tiny(pipeline_depth=2, n_episodes=8)
    state, df = train_pipelined(
        cfg, guard=True, max_retries=2, window_fault=window_fault
    )
    g = df.attrs["guard"]
    p = df.attrs["pipeline"]
    if not _params_ok(state):
        raise CellFailed("poisoned window reached the params")
    n_blocks = p["blocks"]
    if persistent:
        ok = (
            g["redraws"] == 2
            and g["skipped"] == 1
            and p["publishes"] == n_blocks - 1
        )
        outcome = "degraded"  # one training block lost, contained
    else:
        ok = (
            g["redraws"] == 1
            and g["skipped"] == 0
            and p["publishes"] == n_blocks
        )
        outcome = "survived"
    if not ok:
        raise CellFailed(
            f"window guard accounting broke: guard={g}, pipeline="
            f"{ {k: p[k] for k in ('publishes', 'staleness')} }"
        )
    final = _final_return(df)
    return {
        "outcome": outcome,
        "counters": {**g, "publishes": p["publishes"]},
        "final_return": final if math.isfinite(final) else None,
        "clean_return": None,
        "detail": (
            f"{intensity} all-NaN rollout window at block 1, depth 2, "
            "max_retries 2"
        ),
    }


def _run_pipeline_faulted(intensity: str) -> dict:
    """A depth-2 pipelined run under the standard drop+NaN+stale plan
    with sanitize: the learner-side guard + publisher validation must
    keep the run finite and publishing."""
    import numpy as np

    from rcmarl_tpu.lint.configs import tiny_faulted_cfg
    from rcmarl_tpu.pipeline.trainer import train_pipelined

    depth = int(intensity.removeprefix("depth"))  # lint: disable=host-sync
    cfg = tiny_faulted_cfg(False, pipeline_depth=depth, n_episodes=8)
    state, df = train_pipelined(cfg)
    clean_key = ("pipeline_clean", depth)
    if clean_key not in _CLEAN_CACHE:
        _, cdf = train_pipelined(
            _tiny(pipeline_depth=depth, n_episodes=8)
        )
        _CLEAN_CACHE[clean_key] = _final_return(cdf)
    clean = _CLEAN_CACHE[clean_key]
    g = df.attrs["guard"]
    p = df.attrs["pipeline"]
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    final = _final_return(df)
    if not _params_ok(state) or not np.isfinite(returns[-RETURN_WINDOW:]).all():
        outcome = "failed"
        final = None
    elif g["skipped"] > 0 or not _within_band(final, clean):
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": {**g, "publishes": p["publishes"]},
        "final_return": final,
        "clean_return": clean,
        "detail": f"depth {depth}, drop+NaN+stale plan, sanitize+guard",
    }


# --------------------------------------------------------------------------
# composed: pipelined gossip fleets (pipeline x gossip x canary in ONE
# topology — the cells that prove composition degrades no worse than
# its pieces)
# --------------------------------------------------------------------------


def _gala_cfg(**overrides):
    base = dict(
        replicas=4,
        gossip_every=2,
        gossip_graph="full",
        gossip_H=1,
        pipeline_depth=2,
        canary_band=0.5,
        n_episodes=8,
    )
    base.update(overrides)
    return _tiny(**base)


def _gala_cell(cfg, readmit_after: int = 0) -> dict:
    """One composed pipelined-gossip-canary run under ``cfg``'s replica
    fault plan, classified like :func:`_gossip_cell` with one extra
    prong: a non-finite SERVED policy is an unconditional failure (the
    canary/deploy gate is part of the composed containment contract)."""
    import numpy as np

    from rcmarl_tpu.parallel.gala import train_gala

    states, df = train_gala(cfg, readmit_after=readmit_after)
    g = df.attrs["gossip"]
    c = df.attrs["canary"]
    byz = set(g["byzantine"])
    healthy = [
        ok for r, ok in enumerate(g["replica_healthy"]) if r not in byz
    ]
    returns = np.asarray(df["True_team_returns"].values, dtype=float)  # lint: disable=host-sync
    final = _final_return(df)
    counters = {
        k: g[k]
        for k in ("rounds", "rollbacks", "excluded", "readmitted",
                  "nonfinite", "deficit")
    }
    counters["skipped"] = sum(df.attrs["guard"]["replica_skipped"])
    counters["deploys"] = c["deploys"]
    counters["deploy_rejects"] = c["deploy_rejects"]
    clean_cfg = cfg.replace(
        fault_plan=None, replica_fault_plan=None, consensus_sanitize=False
    )
    clean_key = ("gala_clean", clean_cfg)
    if clean_key not in _CLEAN_CACHE:
        from rcmarl_tpu.parallel.gala import train_gala as tg

        _, cdf = tg(clean_cfg, guard=False)
        _CLEAN_CACHE[clean_key] = _final_return(cdf)
    clean = _CLEAN_CACHE[clean_key]
    if (
        not all(healthy)
        or not np.isfinite(returns[-RETURN_WINDOW:]).all()
        or not c["deploy_healthy"]
    ):
        outcome = "failed"
        final = final if math.isfinite(final) else None
    elif (
        g["rollbacks"] > 0
        or any(g["quarantined"])
        or counters["skipped"] > 0
        or not _within_band(final, clean)
    ):
        outcome = "degraded"
    else:
        outcome = "survived"
    return {
        "outcome": outcome,
        "counters": counters,
        "final_return": final,
        "clean_return": clean,
        "detail": (
            f"R={cfg.replicas} {cfg.gossip_graph} graph, "
            f"gossip_H={cfg.gossip_H}, mix={cfg.gossip_mix}, "
            f"depth={cfg.pipeline_depth}, band={cfg.canary_band}, "
            f"readmit_after={readmit_after}"
        ),
    }


def _run_gala_byzantine(intensity: str) -> dict:
    """The replica_byzantine cell INSIDE a depth-2 pipelined fleet with
    a canary-gated deploy: trimmed-mean gossip at H=1 must keep the
    composed run inside the same clean band the flat cell holds."""
    from rcmarl_tpu.faults import ReplicaFaultPlan

    return _gala_cell(
        _gala_cfg(
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode=intensity
            )
        )
    )


def _run_gala_byzantine_mean(intensity: str) -> dict:
    """The documented-fail comparison arm, composed: the same Byzantine
    replica against the UNHARDENED plain-mean mix poisons every replica
    segment downstream of the first round."""
    from rcmarl_tpu.faults import ReplicaFaultPlan

    return _gala_cell(
        _gala_cfg(
            gossip_mix="mean",
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode=intensity
            ),
        )
    )


def _run_gala_window(intensity: str) -> dict:
    """Stale/poisoned actor windows feeding ONE replica's learner inside
    the fleet (the composed seam of pipeline_window): the fault burns
    exactly that replica's redraw/skip budget, and a skipping replica
    flaps through quarantine and streak readmission — counters exact,
    every other replica untouched."""
    from rcmarl_tpu.parallel.gala import train_gala

    persistent = intensity == "persistent"

    def window_fault(r, b, attempt, fresh, m):
        if r == 1 and b == 1 and (persistent or attempt == 0):
            return _nan_bomb_window(fresh, m)
        return fresh, m

    cfg = _tiny(
        replicas=2, pipeline_depth=2, gossip_every=2,
        gossip_graph="full", gossip_H=0,
        n_episodes=12 if persistent else 8,
    )
    states, df = train_gala(
        cfg, guard=True, max_retries=2, window_fault=window_fault,
        readmit_after=1 if persistent else 0,
    )
    g = df.attrs["guard"]
    go = df.attrs["gossip"]
    p = df.attrs["pipeline"]
    if not _params_ok(states):
        raise CellFailed("poisoned window reached a replica's params")
    if persistent:
        ok = (
            g["replica_redraws"] == [0, 2]
            and g["replica_skipped"] == [0, 1]
            and go["excluded"] == 1
            and go["readmitted"] == 1
            and go["quarantined"] == [0, 0]
            and go["rollbacks"] == 0
        )
        outcome = "degraded"  # one replica-block lost + one mix sat out
    else:
        ok = (
            g["replica_redraws"] == [0, 1]
            and g["replica_skipped"] == [0, 0]
            and go["excluded"] == 0
            and go["rollbacks"] == 0
        )
        outcome = "survived"
    if not ok:
        raise CellFailed(
            f"composed window-guard accounting broke: guard={g}, "
            f"gossip={ {k: go[k] for k in ('excluded', 'readmitted', 'quarantined', 'rollbacks')} }"
        )
    final = _final_return(df)
    return {
        "outcome": outcome,
        "counters": {
            "redraws": sum(g["replica_redraws"]),
            "skipped": sum(g["replica_skipped"]),
            "excluded": go["excluded"],
            "readmitted": go["readmitted"],
            "publishes": p["publishes"],
        },
        "final_return": final if math.isfinite(final) else None,
        "clean_return": None,
        "detail": (
            f"{intensity} all-NaN window at replica 1 block 1, R=2 "
            "depth 2, max_retries 2"
            + (", readmit_after 1" if persistent else "")
        ),
    }


def _run_gala_canary_race(intensity: str) -> dict:
    """A poisoned mix racing the canary-gated deploy publish at the SAME
    segment boundary: mean-mix + a NaN Byzantine replica poisons the
    winner's params in the instant between its (finite, eligible)
    segment metrics and the deploy offer. Training is documented-lost
    (that is gala_byzantine_mean's row); THIS cell's contract is the
    serving gate — every poisoned offer must be rejected and the served
    policy must stay finite last-good."""
    from rcmarl_tpu.faults import ReplicaFaultPlan, params_finite
    from rcmarl_tpu.parallel.gala import train_gala

    cfg = _gala_cfg(
        gossip_mix="mean",
        replica_fault_plan=ReplicaFaultPlan(
            byzantine_replicas=(3,), byzantine_mode=intensity
        ),
    )
    states, df = train_gala(cfg)
    c = df.attrs["canary"]
    if not c["deploy_healthy"]:
        raise CellFailed("poisoned mix reached the served policy")
    if c["deploy_rejects"] + c["rejects"] < 1:
        raise CellFailed(
            f"no deploy-side rejection fired against the poisoned "
            f"winner: {c}"
        )
    return {
        "outcome": "survived",
        "counters": {
            k: c[k]
            for k in ("evals", "accepts", "rejects", "deploys",
                      "deploy_rejects")
        },
        "final_return": None,
        "clean_return": None,
        "detail": (
            "mean-mix NaN poisoning raced the deploy publish; gate "
            "rejected, served policy stayed finite"
        ),
    }


# --------------------------------------------------------------------------
# serving: stale candidates (canary) + request-level overload
# --------------------------------------------------------------------------


def _run_canary_stale(intensity: str) -> dict:
    """A checksum-valid, fully finite candidate whose POLICY is below
    the band — the case no file/finiteness guard can catch — must be
    rejected by the canary gate with the engine kept BITWISE on the
    incumbent, and a healthy re-publish must promote."""
    import jax
    import numpy as np

    from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher
    from rcmarl_tpu.serve.engine import ServeEngine
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.checkpoint import save_checkpoint

    cfg = _tiny()
    incumbent = init_train_state(cfg, jax.random.PRNGKey(0))  # lint: disable=prng-int-seed
    candidate = init_train_state(cfg, jax.random.PRNGKey(123))  # lint: disable=prng-int-seed
    with tempfile.TemporaryDirectory() as d:
        path = Path(d) / "checkpoint.npz"
        save_checkpoint(path, incumbent, cfg)
        eng = ServeEngine(path)
        gate = CanaryGate(
            cfg, incumbent.desired, incumbent.initial, band=0.05, blocks=1
        )
        watcher = CanaryWatcher(eng, gate)
        # pin the incumbent reference above any achievable return, so
        # the finite fresh-init candidate is deterministically below
        # the floor (the committed canary_gate.json experiment carries
        # the trained-vs-stale version of this arm)
        gate.incumbent_return = 0.0
        save_checkpoint(path, candidate, cfg)
        if watcher.poll() is not False:
            raise CellFailed("band-violating candidate was promoted")
        if gate.counters["rejects"] != 1 or not eng.degraded:
            raise CellFailed(
                f"reject ledger wrong: gate={gate.counters}, "
                f"engine degraded={eng.degraded}"
            )
        # the contract this cell names: after the reject the engine is
        # BITWISE the incumbent policy (not just counter-correct)
        from rcmarl_tpu.serve.engine import serve_block, stack_actor_rows

        obs = jax.random.normal(
            jax.random.PRNGKey(5), (4, cfg.n_agents, cfg.obs_dim)  # lint: disable=prng-int-seed
        )
        key = jax.random.PRNGKey(9)  # lint: disable=prng-int-seed
        _, p = eng.serve(obs, key=key)
        _, p_inc = serve_block(
            cfg, stack_actor_rows(incumbent.params, cfg), obs, key
        )
        if not np.array_equal(np.asarray(p), np.asarray(p_inc)):  # lint: disable=host-sync
            raise CellFailed(
                "post-reject serving is not bitwise the incumbent"
            )
        # recovery: set a real incumbent reference; the same candidate
        # now clears the band and promotes
        gate.set_incumbent(incumbent.params)
        save_checkpoint(path, candidate, cfg)
        if watcher.poll() is not True:
            raise CellFailed("gate wedged after the rejection")
        return {
            "outcome": "survived",
            "counters": {**gate.counters, **eng.counters},
            "final_return": (
                None
                if gate.last is None
                else gate.last.get("candidate_return")
            ),
            "clean_return": None,
            "detail": (
                "stale-policy candidate band-rejected, incumbent kept, "
                "re-publish promoted"
            ),
        }


#: Deterministic synthetic service model for the overload cells: the
#: queue math is the system under test, and a measured launch would
#: leak wall-clock noise into a gated ledger row.
_SERVICE_S = 0.001
_MAX_BATCH = 16
_MAX_WAIT = 0.002
_SHED_AFTER = 0.002
_OVERLOAD_X = 4.0  # offered load, as a multiple of capacity


def _run_overload(intensity: str) -> dict:
    """Request-level overload past the saturation knee through the
    micro-batching queue (deterministic service model): ``noshed`` is
    the documented backlog cliff — p99 beyond the latency bound —
    while ``shed`` must keep p99 within ``LATENCY_BOUND_FACTOR`` x the
    knee-point p99 with the cost ledgered as the shed fraction (the
    deadline-shedding acceptance criterion as a gated cell). The
    ``autoscale`` arm drives a LONGER sustained overload through the
    SLO controller (:mod:`rcmarl_tpu.serve.autoscale`): the fleet must
    scale out, RESTORE the SLO in the steady windows, and end with a
    strictly smaller shed fraction than the static shed arm pays —
    degrade-then-recover, not degrade-forever."""
    from rcmarl_tpu.serve.load import poisson_arrivals, run_load

    capacity = _MAX_BATCH / _SERVICE_S
    knee = run_load(
        lambda fill: _SERVICE_S,
        poisson_arrivals(0, 4000, 0.8 * capacity),
        _MAX_BATCH,
        _MAX_WAIT,
    )
    if intensity == "autoscale":
        from rcmarl_tpu.serve.autoscale import SLOController, autoscale_replay

        slo = LATENCY_BOUND_FACTOR * knee["p99"]
        # a longer sustained plan than the shed/noshed cells: the ramp
        # windows ARE the phenomenon under test
        arrivals = poisson_arrivals(0, 20000, _OVERLOAD_X * capacity)
        static = run_load(
            lambda fill: _SERVICE_S, arrivals, _MAX_BATCH, _MAX_WAIT,
            _SHED_AFTER,
        )
        res = autoscale_replay(
            lambda fill: _SERVICE_S,
            arrivals,
            SLOController(slo_p99=slo, max_scale=8),
            window=0.05,
            max_batch=_MAX_BATCH,
            max_wait=_MAX_WAIT,
            # the deadline IS the SLO here: shed only what would
            # already miss it, so a healthy scaled-out window is
            # genuinely shed-free (the static arm keeps the registry's
            # fixed 2ms deadline — its p99 bound, not an SLO)
            shed_after=slo,
            slo_p99=slo,
        )
        wins = res["windows"]
        frac = res["shed"] / max(1, res["requests"])
        if res["max_scale_used"] <= 1:
            raise CellFailed(
                "the controller never scaled out under sustained "
                f"{_OVERLOAD_X:.0f}x overload"
            )
        if not wins or not wins[-1]["slo_ok"]:
            raise CellFailed(
                "autoscale failed to restore the SLO by the final "
                f"window: p99 {wins[-1]['p99'] * 1e3:.3f}ms vs "
                f"{slo * 1e3:.3f}ms target"
                if wins
                else "autoscale produced no windows"
            )
        if frac >= static["shed_fraction"]:
            raise CellFailed(
                f"autoscale shed fraction {frac:.4f} is not below the "
                f"static shed arm's {static['shed_fraction']:.4f} — "
                "scaling out bought nothing"
            )
        return {
            "outcome": "survived",
            "counters": {
                "slo_ms": round(slo * 1e3, 3),
                "final_p99_ms": round(wins[-1]["p99"] * 1e3, 3),
                "max_scale_used": res["max_scale_used"],
                "final_scale": res["final_scale"],
                "resizes": len(res["resizes"]),
                "shed_fraction": round(frac, 4),
                "static_shed_fraction": round(
                    static["shed_fraction"], 4
                ),
            },
            "final_return": None,
            "clean_return": None,
            "detail": (
                f"{_OVERLOAD_X:.0f}x capacity sustained; scale "
                f"1->{res['max_scale_used']}, SLO restored, shed "
                f"{frac:.1%} vs static {static['shed_fraction']:.1%}"
            ),
        }
    arrivals = poisson_arrivals(0, 4000, _OVERLOAD_X * capacity)
    shed_after = _SHED_AFTER if intensity == "shed" else math.inf
    rep = run_load(
        lambda fill: _SERVICE_S, arrivals, _MAX_BATCH, _MAX_WAIT, shed_after
    )
    bound = LATENCY_BOUND_FACTOR * knee["p99"]
    bounded = rep["p99"] <= bound
    if intensity == "shed":
        if not bounded:
            raise CellFailed(
                f"shedding failed to bound p99: {rep['p99']:.4f}s > "
                f"{bound:.4f}s (= {LATENCY_BOUND_FACTOR}x knee p99)"
            )
        if rep["shed_fraction"] <= 0.0:
            raise CellFailed("overload shed nothing — the cell is idle")
        outcome = "survived"
    else:
        # the shed-free arm PAST the knee is backlog by construction;
        # a bounded p99 here would mean the overload is no overload
        if bounded:
            raise CellFailed(
                "the no-shed overload arm stayed under the bound — "
                "the offered load no longer saturates; retune"
            )
        outcome = "degraded"
    return {
        "outcome": outcome,
        "counters": {
            "p99_ms": round(rep["p99"] * 1e3, 3),
            "knee_p99_ms": round(knee["p99"] * 1e3, 3),
            "shed": rep["shed"],
            "served": rep["served"],
            "shed_fraction": round(rep["shed_fraction"], 4),
        },
        "final_return": None,
        "clean_return": None,
        "detail": (
            f"{_OVERLOAD_X:.0f}x capacity offered, "
            f"shed_after={'off' if shed_after == math.inf else shed_after}"
        ),
    }


# --------------------------------------------------------------------------
# THE REGISTRY
# --------------------------------------------------------------------------

CHAOS_POINTS: Tuple[ChaosPoint, ...] = (
    ChaosPoint(
        "link_drop", "transport",
        "consensus link delivers nothing (NaN payload)",
        "FaultPlan.drop_p + sanitize + guard",
        "sanitize exclusion + degree-deficit fallback",
        "tests/test_faults.py", (("0.2", "survived"), ("0.5", "survived")),
        _link_runner("link_drop"),
    ),
    ChaosPoint(
        "link_nan", "transport",
        "adversarial all-NaN payload bombs on consensus links",
        "FaultPlan.nan_p + sanitize + guard",
        "sanitize exclusion + degree-deficit fallback",
        "tests/test_faults.py", (("0.2", "survived"), ("0.5", "survived")),
        _link_runner("link_nan"),
    ),
    ChaosPoint(
        "link_nan_unsanitized", "transport",
        "NaN bombs with the sanitize kernel OFF (guard-only containment)",
        "FaultPlan.nan_p, guard rollback/skip",
        "trainer guard rails (rollback, bounded retry, skip)",
        "tests/test_faults.py::TestGuardedTraining", (("0.2", "degraded"),),
        _link_runner("link_nan", sanitize=False),
    ),
    ChaosPoint(
        "link_stale", "transport",
        "links replay the sender's stale pre-fit weights",
        "FaultPlan.stale_p + sanitize + guard",
        "trim/clip into the healthy bounds",
        "tests/test_faults.py", (("0.3", "survived"),),
        _link_runner("link_stale"),
    ),
    ChaosPoint(
        "link_flip", "transport",
        "sign-flip corruption of whole link payloads",
        "FaultPlan.flip_p + sanitize + guard",
        "H-trimming (flipped payloads land outside the trim bounds)",
        "tests/test_faults.py", (("0.3", "survived"),),
        _link_runner("link_flip"),
    ),
    ChaosPoint(
        "link_corrupt", "transport",
        "additive Gaussian corruption of link payloads",
        "FaultPlan.corrupt_p/corrupt_scale + sanitize + guard",
        "clip into the trim bounds",
        "tests/test_faults.py", (("0.3", "survived"),),
        _link_runner("link_corrupt"),
    ),
    ChaosPoint(
        "adaptive_collusion", "consensus",
        "omniscient colluding adversary crafting payloads against the "
        "trimmed mean",
        "Roles.ADAPTIVE + Config.adaptive_scale",
        "H-trimming (H >= colluders); H=0 is the documented undefended arm",
        "tests/test_envs.py (adaptive cells), QUALITY.md adaptive section",
        (("h1", "survived"), ("h0", "failed")),
        _run_adaptive,
    ),
    ChaosPoint(
        "mega_sparse_adaptive", "consensus",
        "adaptive collusion at population scale (n=256) over the sparse "
        "time-varying random-geometric graph",
        "Roles.ADAPTIVE x8 + graph_schedule='random_geometric' "
        "(ops/exchange.py sparse data-graph gather)",
        "H-trimming per scheduled neighborhood + validate_graph on "
        "every resample",
        "tests/test_exchange.py, QUALITY.md mega-population section",
        (("h1", "survived"), ("h1_fused", "survived")),
        _run_mega_sparse,
    ),
    ChaosPoint(
        "replica_byzantine", "gossip",
        "an always-adversarial learner replica bombing every gossip round",
        "ReplicaFaultPlan.byzantine_replicas/_mode",
        "trimmed-mean gossip mix at gossip_H + per-replica guard",
        "tests/test_gossip.py, tests/test_gossip_properties.py",
        (("nan", "survived"), ("sign_flip", "survived"),
         ("inf", "survived")),
        _run_byzantine,
    ),
    ChaosPoint(
        "replica_byzantine_mean", "gossip",
        "the same Byzantine replica against the UNHARDENED plain-mean mix",
        "ReplicaFaultPlan.byzantine_replicas + gossip_mix='mean'",
        "none — the documented comparison arm one NaN replica poisons",
        "tests/test_gossip.py::TestGossipTrain", (("nan", "failed"),),
        _run_byzantine_mean,
    ),
    ChaosPoint(
        "replica_link_nan", "gossip",
        "probabilistic NaN bombs on replica gossip links",
        "ReplicaFaultPlan.nan_p",
        "sanitized trimmed mix (per-element exclusion)",
        "tests/test_gossip_properties.py", (("0.3", "survived"),),
        _run_replica_link,
    ),
    ChaosPoint(
        "gossip_flapping", "gossip",
        "replicas flapping unhealthy/healthy under probabilistic "
        "agent-level poisoning (no sanitize)",
        "FaultPlan.nan_p + train_gossip(readmit_after=K)",
        "per-replica rollback + sticky quarantine + K-round readmission",
        "tests/test_gossip.py (readmission cells)",
        (("readmit1", "degraded"),),
        _run_flapping,
    ),
    ChaosPoint(
        "ckpt_bitflip", "checkpoint",
        "byte corruption of the serving checkpoint at a named position",
        "bit flips in leaf payload / __config__ / __meta__ / truncation "
        "/ primary+.prev",
        "payload checksum + .prev rotation + watcher reject/last-good",
        "tests/test_serve.py::TestHotSwap",
        (("payload", "survived"), ("header", "survived"),
         ("meta", "survived"), ("truncate", "survived"),
         ("both", "survived")),
        _run_ckpt_bitflip,
    ),
    ChaosPoint(
        "publish_poison", "publish",
        "a NaN-poisoned in-memory publish candidate offered to the "
        "acting tier",
        "PolicyPublisher(validate=True)",
        "shared params_finite guard, reject + keep last good",
        "tests/test_pipeline.py::TestPolicyPublisher", (("nan", "survived"),),
        _run_publish_poison,
    ),
    ChaosPoint(
        "pipeline_window", "pipeline",
        "poisoned/dropped actor-tier rollout windows in transit between "
        "the tiers",
        "train_pipelined(window_fault=...) (the chaos seam)",
        "window pickup guard: bounded redraws, then skip (no learner "
        "launch, nothing published)",
        "tests/test_pipeline.py (window-guard cells)",
        (("transient", "survived"), ("persistent", "degraded")),
        _run_pipeline_window,
    ),
    ChaosPoint(
        "pipeline_faulted", "pipeline",
        "the standard transport plan under a depth-2 decoupled pipeline",
        "FaultPlan + sanitize through train_pipelined",
        "learner-side guard + publisher validation",
        "tests/test_pipeline.py::TestPipelined", (("depth2", "survived"),),
        _run_pipeline_faulted,
    ),
    ChaosPoint(
        "gala_byzantine", "composed",
        "an always-adversarial learner replica INSIDE a pipelined "
        "gossip fleet with a canary-gated deploy",
        "ReplicaFaultPlan through train_gala (pipeline x gossip x canary)",
        "trimmed-mean gossip mix at gossip_H + per-replica pipeline "
        "guard + deploy validation",
        "tests/test_gala.py",
        (("nan", "survived"), ("sign_flip", "survived")),
        _run_gala_byzantine,
    ),
    ChaosPoint(
        "gala_byzantine_mean", "composed",
        "the same composed Byzantine replica against the UNHARDENED "
        "plain-mean mix",
        "ReplicaFaultPlan + gossip_mix='mean' through train_gala",
        "none — the documented comparison arm one NaN replica poisons",
        "tests/test_gala.py", (("nan", "failed"),),
        _run_gala_byzantine_mean,
    ),
    ChaosPoint(
        "gala_window", "composed",
        "stale/poisoned actor windows feeding one replica's learner "
        "inside the fleet (flapping through quarantine + readmission)",
        "train_gala(window_fault=...) (the composed chaos seam)",
        "per-replica window guard (bounded redraws, skip) + mix "
        "exclusion + sticky quarantine + streak readmission",
        "tests/test_gala.py::TestComposedGuards",
        (("transient", "survived"), ("persistent", "degraded")),
        _run_gala_window,
    ),
    ChaosPoint(
        "gala_canary_race", "composed",
        "a poisoned mean-mix racing the canary-gated deploy publish at "
        "the same segment boundary",
        "ReplicaFaultPlan + gossip_mix='mean' + canary_band through "
        "train_gala",
        "deploy-side params_finite validation + canary gate, served "
        "policy keeps last good",
        "tests/test_gala.py (canary prongs)", (("nan", "survived"),),
        _run_gala_canary_race,
    ),
    ChaosPoint(
        "serve_canary", "serving",
        "a checksum-valid, finite candidate whose POLICY regressed below "
        "the band",
        "CanaryGate/CanaryWatcher (serve --canary_band)",
        "frozen-policy return gate, reject + incumbent keeps serving",
        "tests/test_serve_canary.py", (("stale", "survived"),),
        _run_canary_stale,
    ),
    ChaosPoint(
        "serve_overload", "serving",
        "request-level overload past the saturation knee",
        "offered load >> capacity through the micro-batching queue",
        "deadline shedding (run_load shed_after): bounded p99, ledgered "
        "shed fraction; SLO autoscaler (serve/autoscale.py): scale-out "
        "restores the SLO and undercuts the static shed cost",
        "tests/test_serve_load.py (shed cells), tests/test_autoscale.py",
        (("noshed", "degraded"), ("shed", "survived"),
         ("autoscale", "survived")),
        _run_overload,
    ),
)


def registry_cells() -> Tuple[Tuple[str, str], ...]:
    """Every (point, intensity) cell in canonical order."""
    return tuple(
        (p.name, label) for p in CHAOS_POINTS for label, _ in p.cells
    )


def point_by_name(name: str) -> Optional[ChaosPoint]:
    for p in CHAOS_POINTS:
        if p.name == name:
            return p
    return None
