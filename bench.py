"""Benchmark: full-training env-steps/s on one chip, vs the CPU reference.

Runs the exact reference workload shape (5 agents, 5x5 grid, 20-step
episodes, 50-episode blocks, 10-epoch consensus updates — the published
coop configuration, BASELINE.md) as the device-scanned trainer, vmapped
over a batch of independent seed replicas (the TPU-native equivalent of
the reference's per-seed SGE job array, SURVEY.md C15): at reference model
sizes every op is tiny, so replicas batch onto the chip almost for free
and aggregate throughput is the honest utilization number.

Baseline: the reference's ~2.5 env-steps/s per 4-core CPU job
(BASELINE.md). Timing is measured to a host-side fetch of a value that
depends on the whole computation — on the axon backend,
``block_until_ready`` does not actually wait.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_STEPS_PER_SEC = 2.5  # reference CPU throughput (BASELINE.md)
N_SEEDS = 32  # replicas batched on the single chip
N_BLOCKS = 10  # 500 episodes / 10k env steps per replica per repetition


def main():
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.parallel.seeds import init_states
    from rcmarl_tpu.training import train_scanned

    # Published-run hyperparameters (job.sh: slow_lr=0.002; BASELINE.md)
    cfg = Config(slow_lr=0.002, fast_lr=0.01, seed=100)

    states = init_states(cfg, list(range(100, 100 + N_SEEDS)))
    run = jax.jit(jax.vmap(lambda s: train_scanned(cfg, s, N_BLOCKS)))

    def fetch(states, metrics):
        """Force completion: pull a scalar depending on every replica."""
        return float(jnp.sum(metrics.true_team_returns) + jnp.sum(states.block))

    # Warmup: compile + one full execution (buffers reach steady state).
    states, metrics = run(states)
    fetch(states, metrics)

    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        states, metrics = run(states)
    checksum = fetch(states, metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(checksum)

    steps = reps * N_SEEDS * N_BLOCKS * cfg.block_steps
    sps = steps / dt
    print(
        json.dumps(
            {
                "metric": "train_env_steps_per_sec",
                "value": round(sps, 1),
                "unit": "steps/s",
                "vs_baseline": round(sps / BASELINE_STEPS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
