"""Benchmark: full-training env-steps/s on one chip, vs the CPU reference.

Runs the exact reference workload shape (5 agents, 5x5 grid, 20-step
episodes, 50-episode blocks, 10-epoch consensus updates — the published
coop configuration, BASELINE.md) as the device-scanned trainer, vmapped
over a batch of independent seed replicas (the TPU-native equivalent of
the reference's per-seed SGE job array, SURVEY.md C15): at reference model
sizes every op is tiny, so replicas batch onto the chip almost for free
and aggregate throughput is the honest utilization number.

Baseline: the reference's ~2.5 env-steps/s per 4-core CPU job
(BASELINE.md).

Robustness (round-1 post-mortem, VERDICT.md item 1): the axon TPU tunnel
can be down in two ways — a fast ``RuntimeError: Unable to initialize
backend`` or a silent hang on first device contact. Neither may cost us
the round's only perf artifact again, so the measurement runs in child
subprocesses with hard wall-clock timeouts, orchestrated by this parent:

1. probe the TPU with a tiny program and a short timeout (cheap first
   contact — no compile of the full trainer at risk);
2. on success, run the full TPU measurement (generous timeout: first
   compile of the scanned trainer is slow);
3. retry the probe with backoff a bounded number of times;
4. if the TPU never comes up, fall back to a smaller CPU measurement so
   the driver still records a real, parsable number (tagged
   ``"platform": "cpu"`` — honest, not a fake TPU claim).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"platform", "workload", "attempts", "headline"} plus, on TPU,
"candidates" — the replica-count sweep (one isolated child per count)
whose best aggregate throughput is the headline "value"; "workload"
records the winning shape, and numbers are cross-round comparable only
when workloads match. "headline" is true only for an on-chip
measurement: the CPU fallback sets it false and adds "note", because
its vs_baseline is CPU-vs-CPU, not the chip multiplier BASELINE.md's
>=50x target refers to.
"""

import json
import os
import subprocess
import sys
import time

BASELINE_STEPS_PER_SEC = 2.5  # reference CPU throughput (BASELINE.md)

PROBE_TIMEOUT_S = 240  # tiny program; a healthy tunnel answers in < 60s
TPU_TIMEOUT_S = 1800  # full run incl. first compile (~20-40s) + execution
CPU_TIMEOUT_S = 1200
PROBE_ATTEMPTS = 3
BACKOFF_S = 30.0


def _measure(
    n_seeds: int,
    n_blocks: int,
    reps: int,
    netstack: str = "auto",
    fitstack: str = "auto",
    compute_dtype: str = "float32",
    consensus_impl: str = "xla",
) -> None:
    """Child: run ONE measurement on whatever backend JAX_PLATFORMS says.

    One replica count per child process: a candidate that OOMs, hangs, or
    trips the finite-checksum assert must not be able to destroy another
    candidate's already-finished measurement (the parent holds each
    result as soon as the child prints it).

    Prints one JSON line with the raw measurement; the parent re-emits
    the best candidate with orchestration metadata attached.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rcmarl_tpu.config import Config
    from rcmarl_tpu.parallel.seeds import init_states
    from rcmarl_tpu.training import train_scanned

    # Published-run hyperparameters (job.sh: slow_lr=0.002; BASELINE.md).
    # consensus_impl stays the Config default ('xla' = selection bounds
    # since round 6, log-depth tournament on the flattened one-launch
    # tree layout since round 7 — bitwise-equal to the old full sort, so
    # headline numbers remain trajectory-comparable across rounds; the
    # sort-vs-select A/B arms live in `python -m rcmarl_tpu bench/profile
    # --impl xla xla_sort pallas pallas_sort [--layout flat per_leaf]`).
    # netstack (round 8: the critic+TR one-block epoch, pinned equivalent
    # to the dual-launch arm; default 'auto' = stacked on TPU, dual on
    # CPU — the measured backend policy, PERF.md "netstack") can be
    # forced with `python bench.py --netstack on|off` for an A/B
    # headline; the per-config arms live in
    # `python -m rcmarl_tpu bench --netstack on off`.
    # fitstack (round 10: the cross-flavor fused fit scan, pinned bitwise
    # to the per-flavor arms; default 'auto' = fused on TPU, per-flavor
    # on CPU) and compute_dtype (round 10: bf16 matmul inputs + f32
    # accumulation, QUALITY.md-gated) are A/B-able the same way:
    # `python bench.py --fitstack on|off --compute_dtype bfloat16`.
    # The one-kernel-epoch arms (round 13) ride the same pass-through:
    # `python bench.py --consensus_impl pallas_fused --fitstack pallas`
    # A/Bs the fused epoch against the default; interpreter arms are
    # honest headline:false rows wherever they run (main() below).
    cfg = Config(
        slow_lr=0.002, fast_lr=0.01, seed=100,
        consensus_impl=consensus_impl,
        netstack={"on": True, "off": False}.get(netstack, "auto"),
        fitstack=(
            fitstack
            if fitstack in ("pallas", "pallas_interpret")
            else {"on": True, "off": False}.get(fitstack, "auto")
        ),
        compute_dtype=compute_dtype,
    )

    def fetch(states, metrics):
        """Force completion: pull a scalar depending on every replica."""
        return float(jnp.sum(metrics.true_team_returns) + jnp.sum(states.block))

    states = init_states(cfg, list(range(100, 100 + n_seeds)))
    run = jax.jit(jax.vmap(lambda s: train_scanned(cfg, s, n_blocks)))

    # Hash the lowered program BEFORE timing it: the emitted row is tied
    # to the exact compiled program it measured (the AUDIT.jsonl ledger
    # convention, rcmarl_tpu.lint.cost) — a later "benched arm A,
    # shipped arm B" drift is then detectable from the artifact alone.
    from rcmarl_tpu.utils.profiling import program_fingerprint

    fingerprint = program_fingerprint(run.lower(states))

    # Warmup: compile + one full execution (buffers reach steady state).
    states, metrics = run(states)
    fetch(states, metrics)

    t0 = time.perf_counter()
    for _ in range(reps):
        states, metrics = run(states)
    checksum = fetch(states, metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(checksum)

    steps = reps * n_seeds * n_blocks * cfg.block_steps
    print(
        json.dumps(
            {
                "metric": "train_env_steps_per_sec",
                "value": round(steps / dt, 1),
                "unit": "steps/s",
                "vs_baseline": round(steps / dt / BASELINE_STEPS_PER_SEC, 1),
                "platform": jax.devices()[0].platform,
                "cost_fingerprint": fingerprint,
                # Self-describing workload (VERDICT r2 item 7): TPU and CPU
                # fallback measure different shapes, so cross-round numbers
                # are only comparable when these fields match.
                "workload": {
                    "seeds": n_seeds,
                    "blocks": n_blocks,
                    "reps": reps,
                    "block_steps": cfg.block_steps,
                    "consensus_impl": cfg.consensus_impl,
                    "netstack": cfg.netstack,
                    "fitstack": cfg.fitstack,
                    "compute_dtype": cfg.compute_dtype,
                },
            }
        )
    )


def _measure_serve(
    batch: int,
    steps: int,
    reps: int,
    mode: str = "sample",
    serve_impl: str = "xla",
) -> None:
    """Child: the SERVING headline — actions/sec through the compiled
    batched inference launch at the published reference shape (5
    agents, 20-wide nets), on the requested ``serve_impl`` arm: the XLA
    serve_block chain or the ONE fused forward+key-derivation+sample
    Pallas program (rcmarl_tpu.ops.pallas_serve). A fused arm first
    verifies BITWISE parity (actions AND probs) against the XLA chain
    on the real warmup batch, so a fused headline row carries a parity
    claim the run itself proved.

    Fresh-init parameters: this measures the compiled serving program's
    throughput (the infrastructure number), not a trained policy's
    quality — `python -m rcmarl_tpu serve` serves real checkpoints and
    emits the same row schema. A handful of distinct observation
    buffers are cycled so the loop cannot ride one cached input.
    """
    import jax
    import numpy as np

    from rcmarl_tpu.config import Config
    from rcmarl_tpu.ops.pallas_serve import (
        fused_serve_block,
        resolve_serve_impl,
    )
    from rcmarl_tpu.serve.engine import serve_block, serve_keys, stack_actor_rows
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.profiling import program_fingerprint

    impl = resolve_serve_impl(serve_impl)
    cfg = Config(slow_lr=0.002, fast_lr=0.01, seed=100)
    state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    block = stack_actor_rows(state.params, cfg)
    n_buf = 4
    obs = [
        jax.random.normal(
            jax.random.PRNGKey(i), (batch, cfg.n_agents, cfg.obs_dim)
        )
        for i in range(n_buf)
    ]
    key = serve_keys(0, 0)
    if impl == "xla":
        launch = lambda o, k: serve_block(cfg, block, o, k, mode=mode)
        lowered = serve_block.lower(cfg, block, obs[0], key, mode=mode)
    else:
        interp = impl == "pallas_interpret"
        launch = lambda o, k: fused_serve_block(
            cfg, block, o, k, mode=mode, interpret=interp
        )
        lowered = fused_serve_block.lower(
            cfg, block, obs[0], key, mode=mode, interpret=interp
        )
    fingerprint = program_fingerprint(lowered)
    # warmup: compile + one execution — and on a fused arm, the bitwise
    # parity gate vs the XLA chain on this real batch
    warm_a, warm_p = launch(obs[0], key)
    np.asarray(warm_a)
    if impl != "xla":
        ref_a, ref_p = serve_block(cfg, block, obs[0], key, mode=mode)
        np.testing.assert_array_equal(np.asarray(warm_a), np.asarray(ref_a))
        np.testing.assert_array_equal(np.asarray(warm_p), np.asarray(ref_p))

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        actions = None
        for s in range(steps):
            actions, _ = launch(obs[s % n_buf], jax.random.fold_in(key, s))
        np.asarray(actions)  # completion barrier
        best = min(best, time.perf_counter() - t0)

    total = steps * batch * cfg.n_agents
    print(
        json.dumps(
            {
                "metric": "serve_actions_per_sec",
                "value": round(total / best, 1),
                "unit": "actions/s",
                "platform": jax.devices()[0].platform,
                "cost_fingerprint": fingerprint,
                "serve_impl": impl,
                **({"fused_parity": "bitwise"} if impl != "xla" else {}),
                "workload": {
                    "batch": batch,
                    "steps": steps,
                    "reps": reps,
                    "mode": mode,
                    "serve_impl": impl,
                    "n_agents": cfg.n_agents,
                    "hidden": list(cfg.hidden),
                },
            }
        )
    )


def _measure_pipeline(depth: int, blocks: int, reps: int) -> None:
    """Child: sync-vs-pipelined block wall time at the published
    reference shape — ``blocks`` training blocks through the
    host-looped pipelined trainer (rcmarl_tpu.pipeline), depth 0 being
    the fused synchronous block through the SAME harness, so the pair
    of children is the honest shadow-overlap A/B. Emits one JSON line
    with the measured staleness counters and the combined
    actor+learner program hash (the ledger convention)."""
    import jax

    from rcmarl_tpu.config import Config
    from rcmarl_tpu.pipeline.trainer import (
        pipeline_fingerprint,
        train_pipelined,
    )
    from rcmarl_tpu.utils.profiling import (
        Timer,
        train_block_fingerprint,
    )

    cfg = Config(
        slow_lr=0.002, fast_lr=0.01, seed=100,
        pipeline_depth=depth,
    )
    fingerprint = (
        train_block_fingerprint(cfg)
        if depth == 0
        else pipeline_fingerprint(cfg)
    )
    n_eps = blocks * cfg.n_ep_fixed
    state, df = train_pipelined(cfg, n_episodes=n_eps)  # compile + warm
    attrs = df.attrs["pipeline"]
    best = float("inf")
    for _ in range(reps):
        t = Timer().start()
        state, df = train_pipelined(cfg, n_episodes=n_eps, state=state)
        best = min(best, t.stop(state.params))
        attrs = df.attrs["pipeline"]
    print(
        json.dumps(
            {
                "metric": "pipeline_sec_per_block",
                "value": round(best / blocks, 4),
                "unit": "s/block",
                "env_steps_per_sec": round(
                    blocks * cfg.block_steps / best, 1
                ),
                "platform": jax.devices()[0].platform,
                "cost_fingerprint": fingerprint,
                "workload": {
                    "pipeline_depth": depth,
                    "publish_every": cfg.publish_every,
                    "blocks": blocks,
                    "reps": reps,
                    "n_agents": cfg.n_agents,
                    "hidden": list(cfg.hidden),
                    "staleness_mean": round(attrs["staleness_mean"], 3),
                    "staleness_max": attrs["staleness_max"],
                },
            }
        )
    )


def _probe_tpu(attempts: list) -> bool:
    """THE probe: bounded-retry TPU contact with backoff, shared by
    every orchestrated headline (train, serve, serve_load, pipeline).
    Appends per-attempt records to ``attempts``; True only on a real
    non-CPU backend (JAX can silently fall back to CPU instead of
    raising, and a CPU "probe ok" must never trigger a full-size
    measurement)."""
    for i in range(PROBE_ATTEMPTS):
        res = _run_child(["--probe"], {}, PROBE_TIMEOUT_S)
        attempts.append({"stage": f"probe{i}", **res})
        if res.get("probe") == "ok" and res.get("platform") != "cpu":
            return True
        if i + 1 < PROBE_ATTEMPTS:
            time.sleep(BACKOFF_S * (2**i))
    return False


def _orchestrate_serve(
    tpu_children, cpu_child, metric: str, unit: str, fallback_note: str
) -> int:
    """The ONE serve-family orchestration path (PR-10's discipline,
    deduplicated): probe the TPU with bounded retries; on success run
    each ``(stage, argv)`` TPU child isolated with a hard timeout and
    print the best candidate (``headline: true``, full candidate list
    attached); otherwise — or when every TPU child failed — run the
    smaller CPU fallback child and print its row tagged
    ``"headline": false`` with ``fallback_note`` (an honest number,
    never a fake on-chip claim); total failure emits a structured error
    record. Shared by ``--serve`` and ``--serve_load`` so the fallback
    rows of both axes stay honest by construction."""
    attempts = []
    if _probe_tpu(attempts):
        candidates = []
        for stage, argv in tpu_children:
            res = _run_child(argv, {}, TPU_TIMEOUT_S)
            attempts.append({"stage": stage, **res})
            # a null value is NOT a measurement (e.g. a load sweep whose
            # lightest point was already saturated): it must fall through
            # to the honest fallback, never print as a headline row
            if res.get("value") is not None:
                candidates.append(res)
        if candidates:
            best = max(candidates, key=lambda c: c["value"])
            best["candidates"] = [
                {"value": c["value"], "workload": c["workload"]}
                for c in candidates
            ]
            best["attempts"] = len(attempts)
            best["headline"] = True
            print(json.dumps(best))
            return 0
    res = _run_child(
        cpu_child,
        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
        CPU_TIMEOUT_S,
    )
    attempts.append({"stage": "cpu_fallback", **res})
    # same null-is-not-a-measurement rule as the TPU candidates: a
    # fallback row without a real value (e.g. a load sweep saturated at
    # its lightest point) must become the structured error record below
    if res.get("value") is not None:
        res["attempts"] = len(attempts)
        res["headline"] = False
        res["note"] = fallback_note
        print(json.dumps(res))
        return 0
    print(
        json.dumps(
            {"metric": metric, "value": None, "unit": unit, "error": attempts}
        )
    )
    return 1


def main_pipeline() -> int:
    """`python bench.py --pipeline`: the shadow-overlap headline —
    sync (depth 0) vs pipelined (depth 2) block wall time, with the
    train headline's orchestration discipline: probe the TPU with
    bounded retries, one isolated child per arm, fall back to a
    smaller honest CPU pair tagged ``"headline": false`` (a serial CPU
    core has no overlap to measure — see PERF.md round 12) when the
    tunnel is down."""
    attempts = []
    tpu_ok = _probe_tpu(attempts)

    def arm_pair(blocks: int, reps: int, env, timeout_s, stage: str):
        arms = []
        for depth in (0, 2):
            res = _run_child(
                ["--pipeline_child", "--depth", str(depth),
                 "--blocks", str(blocks), "--reps", str(reps)],
                env,
                timeout_s,
            )
            attempts.append({"stage": f"{stage}_d{depth}", **res})
            if "value" in res:
                arms.append(res)
        return arms

    arms = []
    if tpu_ok:
        arms = arm_pair(10, 3, {}, TPU_TIMEOUT_S, "tpu_pipeline")
    headline = tpu_ok and len(arms) == 2
    if len(arms) != 2:
        # the train/serve headline discipline: a probe that succeeded
        # but children that failed must STILL leave an honest CPU pair,
        # not a missing measurement
        arms = arm_pair(
            4, 2,
            {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
            CPU_TIMEOUT_S, "cpu_pipeline",
        )
    if len(arms) == 2:
        sync, piped = arms
        out = dict(piped)
        out["sync_sec_per_block"] = sync["value"]
        out["shadow_speedup"] = round(sync["value"] / piped["value"], 3)
        out["attempts"] = len(attempts)
        out["headline"] = headline
        if not headline:
            out["note"] = (
                "TPU backend unavailable; CPU fallback pair — a serial "
                "core executes the two tiers back to back, so "
                "shadow_speedup here measures host-loop overhead only, "
                "NOT the on-chip overlap claim (PERF.md round 12; the "
                "TPU refit is queued in tpu_session.sh)"
            )
        print(json.dumps(out))
        return 0
    print(
        json.dumps(
            {
                "metric": "pipeline_sec_per_block",
                "value": None,
                "unit": "s/block",
                "error": attempts,
            }
        )
    )
    return 1


def _probe() -> None:
    """Child: the cheapest possible end-to-end device contact."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    assert float((x @ x).sum()) == 128.0 * 128 * 128
    print(json.dumps({"probe": "ok", "platform": jax.devices()[0].platform}))


def _arm_arg(argv, flag: str, choices) -> str:
    """The validated value of an A/B arm flag in ``argv`` (a missing or
    out-of-set value is a hard error, not a silent default fallback — a
    TPU A/B round must not quietly measure the wrong arm)."""
    i = argv.index(flag)
    if i + 1 >= len(argv) or argv[i + 1] not in choices:
        sys.exit(f"{flag} requires one of: " + ", ".join(choices))
    return argv[i + 1]


def _netstack_arg(argv) -> str:
    return _arm_arg(argv, "--netstack", ("on", "off", "auto"))


def _run_child(argv, env_overrides, timeout_s):
    """Run this script as a child with a hard timeout.

    Returns the parsed JSON from the child's last stdout line, or an
    error dict {"error": ...} — never raises.
    """
    env = dict(os.environ)
    env.update(env_overrides)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *argv],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s}s"}
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    if proc.returncode != 0 or not lines:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        return {"error": f"rc={proc.returncode}: " + " | ".join(tail)[-400:]}
    try:
        return json.loads(lines[-1])
    except json.JSONDecodeError:
        return {"error": f"unparsable child output: {lines[-1][:200]}"}


def main_serve() -> int:
    """`python bench.py --serve`: the SERVING headline (actions/sec)
    through the shared :func:`_orchestrate_serve` path — a TPU batch
    sweep one isolated child each (throughput grows with the request
    batch until the chip saturates), or the smaller honest CPU
    fallback."""
    return _orchestrate_serve(
        tpu_children=[
            (
                f"tpu_serve_{batch}_{impl}",
                ["--serve_child", "--batch", str(batch), "--steps", "50",
                 "--reps", "3", "--serve_impl", impl],
            )
            for batch in (4096, 32768, 131072)
            # both arms per batch: the candidate list IS the fused-vs-XLA
            # A/B, and the headline is whichever program actually wins
            # on-chip (the fused child parity-gates itself before timing)
            for impl in ("xla", "pallas")
        ],
        cpu_child=["--serve_child", "--batch", "1024", "--steps", "20",
                   "--reps", "2"],
        metric="serve_actions_per_sec",
        unit="actions/s",
        fallback_note=(
            "TPU backend unavailable; CPU fallback serving measurement "
            "— an honest actions/sec number, NOT an on-chip serving "
            "claim (BENCH_SERVE.jsonl headline discipline)"
        ),
    )


def _measure_serve_load(
    max_batch: int,
    max_wait_ms: float,
    loads,
    requests: int,
    mode: str = "sample",
    arrival: str = "poisson",
    shed_after_ms: float = None,
) -> None:
    """Child: the latency-under-load measurement — a deterministic
    arrival sweep through the micro-batching queue in front of the
    compiled ``serve_block`` program at the published reference shape
    (rcmarl_tpu.serve.load). Every launch is the PADDED ``max_batch``
    shape (one compile for the whole sweep — the fleet retrace case's
    shape discipline), service times are REAL timed launches on this
    backend, and the queue/arrival clock is simulated and replayable.
    Emits ONE JSON line: per-load p50/p95/p99 latency + queue depth +
    utilization points, and the saturation knee as the headline
    "value" (the highest offered load still under the knee)."""
    import jax

    from rcmarl_tpu.config import Config
    from rcmarl_tpu.serve.engine import serve_block, serve_keys, stack_actor_rows
    from rcmarl_tpu.serve.load import (
        saturation_knee,
        serve_service_fn,
        sweep_load,
    )
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.profiling import program_fingerprint

    cfg = Config(slow_lr=0.002, fast_lr=0.01, seed=100)
    state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    block = stack_actor_rows(state.params, cfg)
    obs_shape = (max_batch, cfg.n_agents, cfg.obs_dim)
    fingerprint = program_fingerprint(
        serve_block.lower(
            cfg,
            block,
            jax.ShapeDtypeStruct(obs_shape, "float32"),
            serve_keys(0, 0),
            mode=mode,
        )
    )
    service = serve_service_fn(cfg, block, max_batch, mode=mode, seed=0)
    max_wait = max_wait_ms / 1000.0
    import math as _math

    shed_after = (
        _math.inf if shed_after_ms is None else shed_after_ms / 1000.0
    )
    points = sweep_load(
        service, loads, requests, max_batch, max_wait, seed=0,
        arrival=arrival, shed_after=shed_after,
    )
    for p in points:
        # humane units for the committed rows: latency in ms
        for k in ("p50", "p95", "p99", "mean_latency", "service_mean"):
            p[k + "_ms"] = round(p.pop(k) * 1000.0, 3)
        p["utilization"] = round(p["utilization"], 4)
        p["fill_mean"] = round(p["fill_mean"], 1)
        p["queue_depth_mean"] = round(p["queue_depth_mean"], 1)
        # the deadline-shedding ledger rides EVERY row (0.0 = shed-free)
        p["shed_fraction"] = round(p["shed_fraction"], 4)
    knee = saturation_knee(
        [
            dict(p, p99=p["p99_ms"], utilization=p["utilization"])
            for p in points
        ]
    )
    print(
        json.dumps(
            {
                "metric": "serve_load_knee",
                "value": knee,
                "unit": "req/s",
                "platform": jax.devices()[0].platform,
                "cost_fingerprint": fingerprint,
                "points": points,
                "workload": {
                    "max_batch": max_batch,
                    "max_wait_ms": max_wait_ms,
                    "shed_after_ms": shed_after_ms,
                    "loads": list(loads),
                    "requests": requests,
                    "mode": mode,
                    "arrival": arrival,
                    "n_agents": cfg.n_agents,
                    "hidden": list(cfg.hidden),
                },
            }
        )
    )


def main_serve_load() -> int:
    """`python bench.py --serve_load`: latency vs offered load through
    the micro-batching queue (p50/p99 + the saturation knee), on the
    SAME orchestration path as `--serve`: the TPU sweep spans loads up
    past the chip's expected knee; the CPU fallback sweeps a smaller
    load range sized to this host's measured serving capacity — an
    honest latency curve, not an on-chip SLO claim. Rows land in
    BENCH_SERVE.jsonl (tpu_session.sh tees them)."""
    return _orchestrate_serve(
        tpu_children=[
            (
                "tpu_serve_load",
                ["--serve_load_child", "--max_batch", "4096",
                 "--max_wait_ms", "5",
                 "--loads", "1e5,1e6,5e6,2e7,8e7",
                 "--requests", "100000"],
            ),
            (
                "tpu_serve_load_bursty",
                ["--serve_load_child", "--max_batch", "4096",
                 "--max_wait_ms", "5",
                 "--loads", "1e5,1e6,5e6,2e7,8e7",
                 "--requests", "100000", "--arrival", "bursty"],
            ),
            (
                # the deadline-shedding arm: same sweep with a 10ms shed
                # deadline, so the past-the-knee points report a bounded
                # p99 + an explicit shed fraction instead of backlog
                "tpu_serve_load_shed",
                ["--serve_load_child", "--max_batch", "4096",
                 "--max_wait_ms", "5", "--shed_after_ms", "10",
                 "--loads", "1e5,1e6,5e6,2e7,8e7",
                 "--requests", "100000"],
            ),
        ],
        # the CPU fallback sweep MUST cross this host's capacity (~2e5
        # req/s at B=256 on the measured serve rows) or the "knee" is a
        # truncation artifact: the top loads sit well past it and the
        # request count is sized so overload backlog dominates max_wait
        cpu_child=["--serve_load_child", "--max_batch", "256",
                   "--max_wait_ms", "10",
                   "--loads", "2e4,8e4,2e5,5e5,1.5e6",
                   "--requests", "20000"],
        metric="serve_load_knee",
        unit="req/s",
        fallback_note=(
            "TPU backend unavailable; CPU fallback latency-vs-load "
            "sweep — honest p50/p99 + knee for THIS host's serving "
            "capacity, NOT an on-chip SLO claim (BENCH_SERVE.jsonl "
            "headline discipline)"
        ),
    )


def main() -> int:
    # headline A/B arm: `python bench.py --netstack on|off` forces the
    # stacked / dual-launch arm in every child measurement (default:
    # the 'auto' backend policy)
    netstack_argv = (
        ["--netstack", _netstack_arg(sys.argv)]
        if "--netstack" in sys.argv
        else []
    )
    # round-10 A/B arms ride the same pass-through
    if "--fitstack" in sys.argv:
        netstack_argv += [
            "--fitstack",
            _arm_arg(
                sys.argv,
                "--fitstack",
                ("on", "off", "auto", "pallas", "pallas_interpret"),
            ),
        ]
    if "--compute_dtype" in sys.argv:
        netstack_argv += [
            "--compute_dtype",
            _arm_arg(sys.argv, "--compute_dtype", ("float32", "bfloat16")),
        ]
    if "--consensus_impl" in sys.argv:
        from rcmarl_tpu.config import CONSENSUS_IMPLS

        netstack_argv += [
            "--consensus_impl",
            _arm_arg(sys.argv, "--consensus_impl", tuple(CONSENSUS_IMPLS)),
        ]
    # interpreter arms (fused-consensus or fit-scan kernel) are test
    # vehicles, never hardware claims — force headline:false even on-chip
    interp_arm = any(a.endswith("_interpret") for a in netstack_argv)
    attempts = []
    # 1-3: probe the TPU, with bounded retries + backoff on any failure
    # (covers both the fast RuntimeError and the silent-hang mode).
    tpu_ok = _probe_tpu(attempts)

    if tpu_ok:
        # Replica-count sweep, ONE CHILD EACH: aggregate throughput grows
        # with replica batching until the chip saturates, and a candidate
        # that OOMs or hangs must not cost the others' results. The first
        # (smallest) candidate is the proven-safe round-2 workload.
        candidates = []
        for n_seeds in (32, 128, 512):
            res = _run_child(
                ["--child", "--seeds", str(n_seeds), "--blocks", "10",
                 "--reps", "3", *netstack_argv],
                {},
                TPU_TIMEOUT_S,
            )
            attempts.append({"stage": f"tpu_measure_{n_seeds}", **res})
            if "value" in res:
                candidates.append(res)
        if candidates:
            best = max(candidates, key=lambda c: c["value"])
            best["candidates"] = [
                {"value": c["value"], "workload": c["workload"]}
                for c in candidates
            ]
            best["attempts"] = len(attempts)
            # The on-chip number BASELINE.md's >=50x target is about
            # (interpreter arms excluded: not a hardware claim).
            best["headline"] = not interp_arm
            print(json.dumps(best))
            return 0

    # Fallback: a smaller CPU measurement — still a real end-to-end number
    # the driver can parse, honestly tagged with its platform.
    res = _run_child(
        ["--child", "--seeds", "4", "--blocks", "2", "--reps", "1",
         *netstack_argv],
        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
        CPU_TIMEOUT_S,
    )
    attempts.append({"stage": "cpu_measure", **res})
    if "value" in res:
        res["attempts"] = len(attempts)
        # Self-distinguishing fallback (VERDICT r3 weak 5): a CPU number
        # divided by the CPU baseline is NOT the chip multiplier, and no
        # driver parsing vs_baseline should be able to mistake it for one.
        res["headline"] = False
        res["note"] = (
            "TPU backend unavailable; CPU fallback measurement — "
            "vs_baseline here is CPU-vs-CPU, NOT the on-chip multiplier. "
            "Last TPU headline: the most recent BENCH_r*.json with "
            'platform "tpu" (artifacts from round 4 on also carry '
            '"headline": true there)'
        )
        print(json.dumps(res))
        return 0

    # Total failure: emit a structured record so the round still has an
    # artifact explaining what happened.
    print(
        json.dumps(
            {
                "metric": "train_env_steps_per_sec",
                "value": None,
                "unit": "steps/s",
                "vs_baseline": None,
                "error": attempts,
            }
        )
    )
    return 1


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe()
    elif "--serve_load_child" in sys.argv:
        args = sys.argv
        _measure_serve_load(
            max_batch=int(args[args.index("--max_batch") + 1]),
            max_wait_ms=float(args[args.index("--max_wait_ms") + 1]),
            loads=[
                float(x)
                for x in args[args.index("--loads") + 1].split(",")
            ],
            requests=int(args[args.index("--requests") + 1]),
            mode=(
                _arm_arg(args, "--mode", ("sample", "greedy"))
                if "--mode" in args
                else "sample"
            ),
            arrival=(
                _arm_arg(args, "--arrival", ("poisson", "bursty"))
                if "--arrival" in args
                else "poisson"
            ),
            shed_after_ms=(
                float(args[args.index("--shed_after_ms") + 1])
                if "--shed_after_ms" in args
                else None
            ),
        )
    elif "--serve_load" in sys.argv:
        sys.exit(main_serve_load())
    elif "--serve_child" in sys.argv:
        args = sys.argv
        _measure_serve(
            batch=int(args[args.index("--batch") + 1]),
            steps=int(args[args.index("--steps") + 1]),
            reps=int(args[args.index("--reps") + 1]),
            mode=(
                _arm_arg(args, "--mode", ("sample", "greedy"))
                if "--mode" in args
                else "sample"
            ),
            serve_impl=(
                _arm_arg(
                    args,
                    "--serve_impl",
                    ("auto", "xla", "pallas", "pallas_interpret"),
                )
                if "--serve_impl" in args
                else "xla"
            ),
        )
    elif "--serve" in sys.argv:
        sys.exit(main_serve())
    elif "--pipeline_child" in sys.argv:
        args = sys.argv
        _measure_pipeline(
            depth=int(args[args.index("--depth") + 1]),
            blocks=int(args[args.index("--blocks") + 1]),
            reps=int(args[args.index("--reps") + 1]),
        )
    elif "--pipeline" in sys.argv:
        sys.exit(main_pipeline())
    elif "--child" in sys.argv:
        args = sys.argv
        _measure(
            n_seeds=int(args[args.index("--seeds") + 1]),
            n_blocks=int(args[args.index("--blocks") + 1]),
            reps=int(args[args.index("--reps") + 1]),
            netstack=_netstack_arg(args) if "--netstack" in args else "auto",
            fitstack=(
                _arm_arg(
                    args,
                    "--fitstack",
                    ("on", "off", "auto", "pallas", "pallas_interpret"),
                )
                if "--fitstack" in args
                else "auto"
            ),
            compute_dtype=(
                _arm_arg(args, "--compute_dtype", ("float32", "bfloat16"))
                if "--compute_dtype" in args
                else "float32"
            ),
            consensus_impl=(
                args[args.index("--consensus_impl") + 1]
                if "--consensus_impl" in args
                else "xla"
            ),
        )
    else:
        sys.exit(main())
