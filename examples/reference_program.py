"""The reference's program, verbatim shape, on this framework's twins.

Mirrors the wiring of the reference ``main.py:22-121`` — per-agent model
construction, agent instantiation by label, grid-world setup, a
``train_RPBCAC`` call, and reference-format artifact saves — but every
piece is this framework's compat twin. A user porting scripts from the
reference can diff this file against their own ``main.py`` to see the
1:1 mapping. Runs in ~1 minute on CPU:
``JAX_PLATFORMS=cpu python examples/reference_program.py``.

(The performance path is the fused trainer — ``python -m rcmarl_tpu
train`` or ``examples/quickstart_api.py``; this compat path runs the
object protocol eagerly, exactly like the reference.)
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import numpy as np

from rcmarl_tpu.agents import (
    ReferenceFaultyAgent,
    ReferenceGreedyAgent,
    ReferenceMaliciousAgent,
    ReferenceRPBCACAgent,
)
from rcmarl_tpu.envs import ReferenceGridWorld
from rcmarl_tpu.models.mlp import init_mlp
from rcmarl_tpu.training import train_RPBCAC

# --- reference main.py:25-44 flag surface, as plain values ---------------
args = {
    "n_agents": 5,
    "agent_label": ["Cooperative"] * 4 + ["Greedy"],
    "in_nodes": [[0, 1, 2, 3], [1, 2, 3, 4], [2, 3, 4, 0], [3, 4, 0, 1], [4, 0, 1, 2]],
    "n_actions": 5,
    "n_states": 2,
    # smoke-test hook (tests/test_examples.py) halves this
    "n_episodes": 20 if os.environ.get("RCMARL_EXAMPLE_FAST") == "1" else 40,
    "max_ep_len": 20,
    "n_ep_fixed": 10,
    "n_epochs": 2,
    "slow_lr": 0.002,
    "fast_lr": 0.01,
    "batch_size": 200,
    "buffer_size": 400,
    "gamma": 0.9,
    "H": 1,
    "common_reward": False,
    "verbose": False,
}

np.random.seed(100)  # reference main.py:46 seeding
desired_state = np.random.randint(0, 5, size=(args["n_agents"], 2))

# --- per-agent model construction (reference main.py:56-86) --------------
key = jax.random.PRNGKey(100)


def glorot_weights(key, in_dim, out_dim):
    """One network's Keras-style flat weight list (Glorot/zeros init)."""
    params = init_mlp(key, in_dim, (20, 20), out_dim)
    return [np.asarray(x) for wb in params for x in wb]


agents = []
for node, label in enumerate(args["agent_label"]):
    key, ka, kc, kt = jax.random.split(key, 4)
    obs = args["n_agents"] * args["n_states"]
    actor = glorot_weights(ka, obs, args["n_actions"])
    critic = glorot_weights(kc, obs, 1)
    team_reward = glorot_weights(kt, args["n_agents"] * (args["n_states"] + 1), 1)
    # agent instantiation by label (reference main.py:88-104)
    if label == "Cooperative":
        agents.append(ReferenceRPBCACAgent(
            actor, critic, team_reward,
            args["slow_lr"], args["fast_lr"], args["gamma"], args["H"],
        ))
    elif label == "Greedy":
        agents.append(ReferenceGreedyAgent(
            actor, critic, team_reward,
            args["slow_lr"], args["fast_lr"], args["gamma"],
        ))
    elif label == "Faulty":
        agents.append(ReferenceFaultyAgent(
            actor, critic, team_reward, args["slow_lr"], args["gamma"],
        ))
    else:
        agents.append(ReferenceMaliciousAgent(
            actor, critic, team_reward,
            args["slow_lr"], args["fast_lr"], args["gamma"],
        ))

# --- environment (reference main.py:109-116) -----------------------------
env = ReferenceGridWorld(
    nrow=5, ncol=5, n_agents=args["n_agents"],
    desired_state=desired_state, randomize_state=True, scaling=True,
)

# --- train + reference-format artifacts (main.py:117-121) ----------------
weights, sim_data = train_RPBCAC(env, agents, args)
out = Path("/tmp/reference_program_out")
out.mkdir(exist_ok=True)
sim_data.to_pickle(out / "sim_data.pkl")
np.save(out / "pretrained_weights.npy", np.asarray(weights, dtype=object),
        allow_pickle=True)
np.save(out / "desired_state.npy", desired_state)

r = sim_data["True_team_returns"]
print(
    f"trained {args['n_episodes']} episodes on the compat twins: "
    f"first-10 return {r[:10].mean():+.2f} -> last-10 {r[-10:].mean():+.2f}; "
    f"artifacts in {out}"
)
