"""Python-API quickstart: train, inspect, checkpoint, resume, scale out.

The CLI (``python -m rcmarl_tpu train ...``) covers the reference's
workflows; this script shows the same things from Python. Sized to run
in about a minute on CPU (``JAX_PLATFORMS=cpu python
examples/quickstart_api.py``); on a TPU chip crank ``n_episodes`` up.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

# smoke-test hook (tests/test_examples.py): shrink workloads, same code
FAST = os.environ.get("RCMARL_EXAMPLE_FAST") == "1"
EPISODES = 50 if FAST else 200
SMALL_EPISODES = 50 if FAST else 100  # the scale-out walkthrough sections

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.parallel import train_parallel
from rcmarl_tpu.training.trainer import train
from rcmarl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

# 1) A 5-agent cast with one greedy adversary, H=1 trimming — the
#    published "greedy" scenario (reference README).
cfg = Config(
    agent_roles=(Roles.COOPERATIVE,) * 4 + (Roles.GREEDY,),
    in_nodes=circulant_in_nodes(5, 4),
    H=1,
    slow_lr=0.002,
    n_episodes=EPISODES,
    seed=100,
)

# 2) Train. `train` runs block-by-block (host loop over jitted blocks);
#    sim_data is the reference-layout pandas DataFrame.
state, sim_data = train(cfg, verbose=False)
r = sim_data["True_team_returns"]
print(f"team return: first 20 eps {r[:20].mean():+.2f} -> last 20 {r[-20:].mean():+.2f}")

# 3) Checkpoint the FULL state (params + Adam moments + buffer + RNG) and
#    resume bit-for-bit.
save_checkpoint("/tmp/quickstart_ck.npz", state, cfg)
restored, stored_cfg = load_checkpoint("/tmp/quickstart_ck.npz")
state2, more = train(cfg, state=restored, verbose=False)
print(f"resumed for another {len(more)} episodes")

# 4) Seed-parallel: several independent replicas as ONE device program
#    (sharded over all available devices).
states, metrics = train_parallel(cfg.replace(n_episodes=SMALL_EPISODES), seeds=[1, 2, 3, 4], n_blocks=2)
print("per-seed mean returns:", metrics.true_team_returns.mean(axis=1).tolist())

# 5) The WHOLE experiment matrix as one program: cells with different
#    scenarios (roles / trim H / reward mode) run as replicas of a single
#    jitted program, their knobs passed as traced data (`sweep --fused`
#    uses exactly this API).
from rcmarl_tpu.parallel import split_matrix_metrics, train_matrix

base = cfg.replace(n_episodes=SMALL_EPISODES)
cells = [
    base.replace(agent_roles=(Roles.COOPERATIVE,) * 5, H=0),  # coop
    base,                                                     # greedy H=1
    base.replace(
        agent_roles=(Roles.COOPERATIVE,) * 4 + (Roles.MALICIOUS,),
        H=1,
        common_reward=True,
    ),                                                        # malicious_global
]
states, metrics = train_matrix(base, cells, seeds=[1, 2], n_blocks=2)
for name, row in zip(
    ["coop H=0", "greedy H=1", "malicious_global H=1"],
    split_matrix_metrics(metrics, len(cells), 2),
):
    seed_means = [float(m.true_team_returns.mean()) for m in row]
    print(f"{name}: per-seed team returns {seed_means}")
