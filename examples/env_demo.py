"""Grid-world smoke demo — the reference ``env_test.py`` (C14) analog.

Runs a few random-policy steps on a small grid and prints positions,
actions, and rewards for eyeball inspection; unlike the reference script
the whole episode executes as one jitted ``lax.scan`` on device.

Run: ``JAX_PLATFORMS=cpu python examples/env_demo.py``
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import jax.numpy as jnp

from rcmarl_tpu.envs.grid_world import (
    GridWorld,
    env_reset,
    env_step,
    scale_reward,
    scale_state,
)

N_AGENTS, N_STEPS = 3, 10


def main():
    env = GridWorld(nrow=3, ncol=3, n_agents=N_AGENTS)
    key = jax.random.PRNGKey(0)
    k_goal, k_pos, k_act = jax.random.split(key, 3)
    desired = env_reset(env, k_goal)
    pos0 = env_reset(env, k_pos)

    @jax.jit
    def episode(pos, keys):
        def step(pos, k):
            actions = jax.random.randint(k, (N_AGENTS,), 0, 5, dtype=jnp.int32)
            npos, reward = env_step(env, pos, desired, actions)
            return npos, (pos, actions, npos, reward)

        return jax.lax.scan(step, pos, keys)

    _, (pos, actions, npos, reward) = episode(
        pos0, jax.random.split(k_act, N_STEPS)
    )

    print(f"goal layout:\n{desired}\n")
    for t in range(N_STEPS):
        print(
            f"t={t}: pos={pos[t].tolist()} a={actions[t].tolist()} "
            f"-> {npos[t].tolist()} r={reward[t].tolist()} "
            f"(scaled r={scale_reward(env, reward[t]).tolist()})"
        )
    print(f"\nscaled observation of final state:\n{scale_state(env, npos[-1])}")


if __name__ == "__main__":
    main()
