"""The point of the framework, in miniature: H-trimmed consensus defeats
a Byzantine agent — and the hardened transport survives NaN bombs.

Part 1 trains the published "malicious" scenario (4 cooperative + 1
malicious agent that transmits a critic/team-reward trained toward MINUS
the team reward — reference ``adversarial_CAC_agents.py:74-182``) twice:
once with no defense (H=0) and once with the paper's trimming defense
(H=1), plus an all-cooperative control. All three casts run as ONE
vmapped, jitted program via the replica machinery (each cast is a
different Config, so they share compiled structure but not a batch — we
just loop).

Part 2 swaps the behavioral adversary for a TRANSPORT one
(rcmarl_tpu.faults): a cooperative cast whose consensus links drop
payloads and deliver NaN bombs. Unsanitized, a single bomb destroys the
run's parameters; with ``consensus_sanitize`` the poisoned entries
become trim-exclusions and training degrades gracefully (the trainer's
guard rails catch whatever slips through).

Sized for CPU (~2 minutes: ``JAX_PLATFORMS=cpu python
examples/resilience_demo.py``); the separation grows with episode count
(the published 8000-episode curves are in PARITY.md rows malicious/H=0
vs H=1).
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.training.trainer import train

# smoke-test hook (tests/test_examples.py): shrink, same code
EPISODES = 100 if os.environ.get("RCMARL_EXAMPLE_FAST") == "1" else 600
CASTS = {
    "all-cooperative": (Roles.COOPERATIVE,) * 5,
    "malicious": (Roles.COOPERATIVE,) * 4 + (Roles.MALICIOUS,),
}

results = {}
for name, roles in CASTS.items():
    for H in ([0] if name == "all-cooperative" else [0, 1]):
        cfg = Config(
            agent_roles=roles,
            in_nodes=circulant_in_nodes(5, 4),
            H=H,
            slow_lr=0.002,
            n_episodes=EPISODES,
            seed=100,
        )
        _, sim_data = train(cfg, verbose=False)
        # final-quarter mean team return of the cooperative agents
        results[(name, H)] = sim_data["True_team_returns"][
            -EPISODES // 4 :
        ].mean()
        print(f"{name:17s} H={H}: {results[(name, H)]:+.2f}")

coop = results[("all-cooperative", 0)]
attacked = results[("malicious", 0)]
defended = results[("malicious", 1)]
print(
    f"\nattack cost without defense: {attacked - coop:+.2f} return"
    f"\nwith H=1 trimming:           {defended - coop:+.2f} return"
)
if defended > attacked:
    print("=> trimming recovered most of the attack damage (the paper's claim)")

# ---- Part 2: transport faults (dropped links + NaN payload bombs) ----
import jax  # noqa: E402
import numpy as np  # noqa: E402

from rcmarl_tpu.faults import FaultPlan  # noqa: E402

print("\ntransport faults: 10% dropped links + 5% NaN payload bombs")
plan = FaultPlan(drop_p=0.1, nan_p=0.05)
for sanitize in (False, True):
    cfg = Config(
        agent_roles=(Roles.COOPERATIVE,) * 5,
        in_nodes=circulant_in_nodes(5, 4),
        H=1,
        slow_lr=0.002,
        n_episodes=EPISODES,
        seed=100,
        fault_plan=plan,
        consensus_sanitize=sanitize,
    )
    # guard=False shows the raw kernel behavior; the default (guarded)
    # trainer would keep even the unsanitized run's params finite by
    # rolling back poisoned blocks.
    state, sim_data = train(cfg, verbose=False, guard=sanitize)
    finite = all(
        bool(np.all(np.isfinite(np.asarray(l))))
        for l in jax.tree.leaves(state.params)
    )
    ret = sim_data["True_team_returns"][-EPISODES // 4 :].mean()
    label = "sanitized " if sanitize else "unsanitized"
    print(
        f"{label} params finite: {finite!s:5s}  "
        f"final return {ret:+.2f}"
        + (f"  vs clean control {coop:+.2f}" if sanitize else "")
    )
print("=> sanitize turns a run-destroying NaN bomb into graceful degradation")
